#!/bin/bash
# Tunnel watcher: probe the TPU backend periodically; on the first healthy
# probe, run the full bench (which snapshots tools/last_good_bench.json) and
# exit. Bounded lifetime so it can never collide with the driver's own
# end-of-round bench run.
#
# Usage: bench_watch.sh [max_seconds] [probe_interval_seconds]
set -u
cd "$(dirname "$0")/.."
MAX=${1:-14400}
INTERVAL=${2:-600}
START=$(date +%s)
while :; do
  now=$(date +%s)
  if (( now - START > MAX )); then
    echo "[watch] lifetime exceeded, exiting without a measurement"
    exit 1
  fi
  # -k: the probe child registers a faulthandler on SIGTERM (stack dump,
  # no exit), so plain timeout's SIGTERM is swallowed — SIGKILL after 10s
  # 150s: the probe now includes a guaranteed-uncached compile, which on
  # a healthy-but-slow tunnel can cost ~40s+ on its own
  out=$(timeout -k 10 150 python bench.py --probe 2>&1)
  if echo "$out" | grep -q "PROBE-OK"; then
    echo "[watch] tunnel healthy at $(date -u +%H:%MZ); running full bench"
    # Cold compile through the tunnel is ~135s (r5): give the bench a
    # budget that fits two real attempts, overridable for manual runs.
    BUDGET=${TONY_BENCH_WATCHDOG_SEC:-900}
    if TONY_BENCH_WATCHDOG_SEC=$BUDGET timeout -k 15 $((BUDGET + 100)) \
        python bench.py > "tools/bench_watch_result.json" 2> \
        "tools/bench_watch_stderr.log" \
        && python -c "
import json, sys
try:
    rec = json.loads(open('tools/bench_watch_result.json').read().strip().splitlines()[-1])
except Exception:
    sys.exit(1)
sys.exit(0 if rec.get('value', 0) > 0 and not rec.get('partial') else 1)"; then
      echo "[watch] bench done (positive on-chip value)"
      cat tools/bench_watch_result.json
      # the tunnel is healthy and the headline is banked: spend the rest
      # of the window proving the orchestrator->chip lifecycle too —
      # bounded by the watcher's own remaining lifetime so it can never
      # hold the single-claim tunnel into the driver's end-of-round bench
      now=$(date +%s)
      e2e_budget=$(( MAX - (now - START) ))
      if (( e2e_budget > 1800 )); then e2e_budget=1800; fi
      if (( e2e_budget >= 300 )); then
        echo "[watch] running on-chip e2e (budget ${e2e_budget}s)"
        timeout -k 15 "$e2e_budget" python tools/onchip_e2e.py || true
      else
        echo "[watch] skipping on-chip e2e: only ${e2e_budget}s left"
      fi
      exit 0
    fi
    # healthy probe but failed/partial/zero bench: keep watching — a
    # wedged-tunnel record has value 0.0 and must NOT stop the watch
    # (r5: grep '\"value\"' matched the 0.0 record and the watch exited).
    echo "[watch] bench failed or zero after healthy probe; will retry"
  fi
  echo "[watch] tunnel down at $(date -u +%H:%MZ); retry in ${INTERVAL}s"
  sleep "$INTERVAL"
done
