"""MFU tuning harness: time llama3_1b_proxy train-step variants on the
live chip and print one JSON line per variant.

Usage: python tools/tune_mfu.py [variant ...]   (default: all)

Variants explore the single-chip levers (VERDICT r2 item 1): batch size,
remat on/off/policy, sequence length. Each runs in-process sequentially —
the tunnel is single-claim, so never run this alongside another TPU job.
"""

from __future__ import annotations

import json
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, ".")
from tony_tpu.models.llama import get_config, llama_init, llama_loss  # noqa: E402
# the ONE peak-FLOPs table + MFU formula, shared with bench.py and the
# trainer's goodput metrics (observability/perf.py)
from tony_tpu.observability.perf import mfu_pct  # noqa: E402
from tony_tpu.train.step import make_train_step  # noqa: E402

# Measured on v5e (2026-07-30): base_b4 (save_flash remat) 67.8%,
# fullremat_b4 65.5%, b2 66.2%, b8 flat, noremat_*/dots_b4 exceed HBM
# (the remote-compile helper then 500s — that error usually means OOM).
VARIANTS: dict[str, dict] = {
    "base_b4":   dict(batch=4, seq=4096),
    "fullremat_b4": dict(batch=4, seq=4096, remat_policy="full"),
    "b8":        dict(batch=8, seq=4096),
    "b2":        dict(batch=2, seq=4096),
    # oom_v5e: tools/aot_rank.py compiled these against a detached v5e
    # topology — 19.64G / 31.31G / 18.18G (unfused_b8) vs 15.75G HBM —
    # so the default sweep skips them instead of burning a ~150s live
    # compile to rediscover the OOM (pass --all to force)
    "noremat_b2": dict(batch=2, seq=4096, remat=False, oom_v5e=True),
    "noremat_b4": dict(batch=4, seq=4096, remat=False, oom_v5e=True),
    "dots_b4":   dict(batch=4, seq=4096, policy="dots_with_no_batch_dims_saveable"),
    "seq8k_b2":  dict(batch=2, seq=8192),
    # fused chunked LM-head CE A/B (preset default is xent_chunk=1024;
    # 0 = full-logits path) — the lever that freed ~4 GB for b8
    "unfused_b4": dict(batch=4, seq=4096, xent_chunk=0),
    "unfused_b8": dict(batch=8, seq=4096, xent_chunk=0, oom_v5e=True),
    "xc512_b8":  dict(batch=8, seq=4096, xent_chunk=512),
    "xc2048_b8": dict(batch=8, seq=4096, xent_chunk=2048),
    # flash-kernel tile sweep (DEFAULT_BLOCK_Q/K = 512 measured 2.05x over
    # 128 on v5e; 1024 and 256 untried on the current kernel stack)
    "blk1024_b4": dict(batch=4, seq=4096, flash_block=1024),
    "blk256_b4": dict(batch=4, seq=4096, flash_block=256),
    "blkq1024k512_b4": dict(batch=4, seq=4096, flash_block_q=1024,
                            flash_block_k=512),
    # batch/seq grid corners never measured on-chip
    "b6":        dict(batch=6, seq=4096),
    "seq8k_b4":  dict(batch=4, seq=8192),
    "seq2k_b8":  dict(batch=8, seq=2048),
    # 8B-geometry single layer (bench's llama3_8b_layer metric, 63.04%
    # at r4's b1/blk512) — can a bigger batch or tile lift it?
    "L8b_b1":    dict(model="8b_layer", batch=1, seq=4096),
    "L8b_b2":    dict(model="8b_layer", batch=2, seq=4096),
    "L8b_b4":    dict(model="8b_layer", batch=4, seq=4096),
    "L8b_blk1024_b2": dict(model="8b_layer", batch=2, seq=4096,
                           flash_block=1024),
    "L8b_noremat_b1": dict(model="8b_layer", batch=1, seq=4096,
                           remat=False),
    "L8b_noremat_b2": dict(model="8b_layer", batch=2, seq=4096,
                           remat=False),
}


def build_config(spec: dict):
    """Resolve a variant spec's preset + config overrides (shared with
    tools/aot_rank.py's offline cost-model ranking)."""
    overrides = {}
    if not spec.get("remat", True):
        overrides["remat"] = False
    if "remat_policy" in spec:
        overrides["remat_policy"] = spec["remat_policy"]
    if "xent_chunk" in spec:
        overrides["xent_chunk"] = spec["xent_chunk"]
    if spec.get("model") == "8b_layer":
        # mirror bench._bench_8b_layer's geometry: one 8B layer, small
        # vocab so embed/head don't dominate
        return get_config("llama3_8b", n_layers=1, vocab_size=8192,
                          max_seq=spec["seq"], **overrides)
    return get_config("llama3_1b_proxy", max_seq=spec["seq"], **overrides)


class variant_globals:
    """Context manager applying a spec's module-global knobs (flash
    block sizes, checkpoint policy) and restoring them on exit — the
    fallible setup shared by the live tuner and the AOT ranker."""

    def __init__(self, spec: dict):
        self.spec = spec

    def __enter__(self):
        import tony_tpu.models.llama as llama_mod
        import tony_tpu.ops.attention as attn_mod
        self._llama_mod, self._attn_mod = llama_mod, attn_mod
        self._real_ckpt = None
        self._saved_blocks = (attn_mod.DEFAULT_BLOCK_Q,
                              attn_mod.DEFAULT_BLOCK_K)
        policy = self.spec.get("policy")
        if policy is not None:
            pol = getattr(jax.checkpoint_policies, policy)
            self._real_ckpt = jax.checkpoint
            llama_mod.jax.checkpoint = partial(self._real_ckpt,
                                               policy=pol)
        attn_mod.DEFAULT_BLOCK_Q = self.spec.get(
            "flash_block_q",
            self.spec.get("flash_block", self._saved_blocks[0]))
        attn_mod.DEFAULT_BLOCK_K = self.spec.get(
            "flash_block_k",
            self.spec.get("flash_block", self._saved_blocks[1]))
        return self

    def __exit__(self, *exc):
        (self._attn_mod.DEFAULT_BLOCK_Q,
         self._attn_mod.DEFAULT_BLOCK_K) = self._saved_blocks
        if self._real_ckpt is not None:
            self._llama_mod.jax.checkpoint = self._real_ckpt
        return False


def run(name: str, spec: dict) -> dict:
    config = build_config(spec)
    # all fallible per-variant setup (policy lookup included) runs inside
    # the try so one bad variant reports its error line, and the with
    # block restores every global for the next variant
    try:
        with variant_globals(spec):
            params = llama_init(config, jax.random.PRNGKey(0))
            optimizer = optax.adamw(3e-4)
            step = make_train_step(partial(llama_loss, config=config),
                                   optimizer)
            opt_state = jax.jit(optimizer.init)(params)
            b, s = spec["batch"], spec["seq"]
            tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                        config.vocab_size, jnp.int32)
            batch = {"inputs": tokens,
                     "targets": jnp.roll(tokens, -1, axis=1)}
            for _ in range(2):
                params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
            t0 = time.monotonic()
            n = 6
            for _ in range(n):
                params, opt_state, loss = step(params, opt_state, batch)
            float(loss)
            dt = (time.monotonic() - t0) / n
            tok_s = b * s / dt
            mfu = mfu_pct(tok_s, config.flops_per_token(s),
                          jax.devices()[0])
            return {"variant": name, "step_s": round(dt, 4),
                    "tok_s": round(tok_s, 1), "mfu_pct": round(mfu, 2)}
    except Exception as e:  # noqa: BLE001 — report and move on (e.g. OOM)
        return {"variant": name,
                "error": f"{type(e).__name__}: {str(e)[:200]}"}


def main() -> None:
    argv = [a for a in sys.argv[1:] if a != "--all"]
    force_all = "--all" in sys.argv[1:]
    names = argv or list(VARIANTS)
    for name in names:
        spec = VARIANTS[name]
        if spec.get("oom_v5e") and not force_all and not argv:
            print(json.dumps({"variant": name,
                              "skipped": "oom_v5e (aot_rank verdict)"}),
                  flush=True)
            continue
        print(json.dumps(run(name, spec)), flush=True)


if __name__ == "__main__":
    main()
