"""Flag bench regressions against the best same-backend baseline.

The self-defending half of the bench (ROADMAP item 5): `bench.py`
appends every emitted headline to `tools/bench_history.jsonl`; this tool
compares the LATEST entry of each (metric, backend) group against the
BEST prior same-backend value and exits nonzero when the drop exceeds
the threshold (default 2%) — so a perf regression fails loudly at the
bench instead of silently eroding the trajectory (the r03→r04 blindness
this guards against).

Rules:
- groups are (metric, backend): a CPU-fallback line can never be judged
  against an on-chip baseline;
- value <= 0 entries (wedged-tunnel fallback headlines pin value to 0.0)
  are markers, not measurements — skipped both as baseline and as the
  judged entry;
- direction comes from the unit: seconds/ms/bytes are lower-is-better,
  everything else (MFU %, tokens/sec) higher-is-better.

Run: python tools/bench_compare.py [--threshold-pct 2]
     [--history tools/bench_history.jsonl] [--metric NAME]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_HISTORY = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_history.jsonl")

LOWER_IS_BETTER_UNITS = ("s", "ms", "sec", "seconds", "bytes", "b")


def load_history(path: str) -> list[dict]:
    entries = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict):
                    entries.append(obj)
    except OSError:
        pass
    return entries


def _measurable(entry: dict) -> bool:
    try:
        return float(entry.get("value", 0.0)) > 0.0
    except (TypeError, ValueError):
        return False


def lower_is_better(unit: str) -> bool:
    return str(unit).strip().lower() in LOWER_IS_BETTER_UNITS


def compare(entries: list[dict], threshold_pct: float,
            metric: str = "") -> list[dict]:
    """Returns one verdict dict per (metric, backend) group that has a
    judgeable latest entry; verdicts with `regression: True` dropped
    more than `threshold_pct` vs the best prior same-backend value."""
    groups: dict[tuple, list[dict]] = {}
    for e in entries:
        m = str(e.get("metric", "") or "")
        if not m or (metric and m != metric):
            continue
        groups.setdefault((m, str(e.get("backend", "") or "")),
                          []).append(e)
    verdicts = []
    for (m, backend), group in sorted(groups.items()):
        latest = next((e for e in reversed(group) if _measurable(e)), None)
        if latest is None:
            continue
        prior = [e for e in group if e is not latest and _measurable(e)]
        if not prior:
            verdicts.append({"metric": m, "backend": backend,
                             "value": float(latest["value"]),
                             "baseline": None, "regression": False,
                             "note": "no prior baseline"})
            continue
        lower = lower_is_better(str(latest.get("unit", "")))
        values = [float(e["value"]) for e in prior]
        baseline = min(values) if lower else max(values)
        value = float(latest["value"])
        if lower:
            drop_pct = 100.0 * (value - baseline) / baseline
        else:
            drop_pct = 100.0 * (baseline - value) / baseline
        verdicts.append({
            "metric": m, "backend": backend, "value": value,
            "unit": str(latest.get("unit", "")),
            "baseline": baseline,
            "baseline_commit": next(
                (str(e.get("commit", "")) for e in prior
                 if float(e["value"]) == baseline), ""),
            "drop_pct": round(drop_pct, 3),
            "regression": drop_pct > threshold_pct,
        })
    return verdicts


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="bench_compare")
    parser.add_argument("--history", default=DEFAULT_HISTORY)
    parser.add_argument("--threshold-pct", type=float, default=2.0,
                        help="fail when the latest measurable entry "
                             "drops more than this vs the best prior "
                             "same-backend value")
    parser.add_argument("--metric", default="",
                        help="judge only this metric")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    entries = load_history(args.history)
    if not entries:
        print(f"no bench history at {args.history} — nothing to judge",
              file=sys.stderr)
        return 0
    verdicts = compare(entries, args.threshold_pct, metric=args.metric)
    if args.json:
        print(json.dumps(verdicts, indent=1, sort_keys=True))
    else:
        for v in verdicts:
            if v.get("baseline") is None:
                print(f"{v['metric']} [{v['backend']}]: "
                      f"{v['value']} ({v['note']})")
                continue
            tag = "REGRESSION" if v["regression"] else "ok"
            print(f"{v['metric']} [{v['backend']}]: {v['value']} "
                  f"{v.get('unit', '')} vs best {v['baseline']} "
                  f"({v.get('baseline_commit') or 'unknown commit'}) — "
                  f"drop {v['drop_pct']}% [{tag}]")
    return 1 if any(v["regression"] for v in verdicts) else 0


if __name__ == "__main__":
    raise SystemExit(main())
