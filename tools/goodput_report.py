"""Print a finished job's time-accounting table from its history dir.

Reads `goodput.json` (the AM's flush of every task's goodput ledger +
the job-level aggregate — observability/perf.py) and, when present,
`spans.json` for the lifecycle context. The table is the operator's
"where did the wall-clock go" answer; tests drive `format_report` to
assert the ledger stays machine-readable.

Usage:
  python tools/goodput_report.py <history_dir | app_dir>  [--json]

Accepts either the per-app history dir itself or an app dir containing
a `history/<app_id>` subtree (the local-backend layout).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tony_tpu import constants as C  # noqa: E402
from tony_tpu.events.history import read_goodput_file  # noqa: E402


def find_history_dir(path: str) -> str:
    """Resolve an app dir / history base down to the dir that holds
    goodput.json (first match wins)."""
    if os.path.isfile(os.path.join(path, C.GOODPUT_FILE)):
        return path
    for dirpath, _, files in sorted(os.walk(path)):
        if C.GOODPUT_FILE in files:
            return dirpath
    return path


def format_report(goodput: dict) -> str:
    """The time-accounting table for one job's goodput dict
    (aggregate_goodput's shape). Pure string building — the testable
    half of the tool."""
    tasks = goodput.get("tasks") or {}
    job = goodput.get("job") or {}
    lines = []
    header = f"{'task':<16} {'phase':<20} {'seconds':>10} {'% wall':>8}"
    lines.append(header)
    lines.append("-" * len(header))
    for task_id, entry in sorted(tasks.items()):
        wall = float(entry.get("wall_s") or 0.0)
        phases = entry.get("phases") or {}
        for phase, secs in sorted(phases.items(), key=lambda kv: -kv[1]):
            if secs <= 0:
                continue
            pct = 100.0 * secs / wall if wall > 0 else 0.0
            lines.append(f"{task_id:<16} {phase:<20} {secs:>10.3f} "
                         f"{pct:>7.1f}%")
        lines.append(f"{task_id:<16} {'= wall':<20} {wall:>10.3f} "
                     f"{'100.0%':>8}")
        mfu = entry.get("mfu_pct")
        if mfu is not None:
            lines.append(f"{task_id:<16} {'mfu':<20} {mfu:>9.2f}%")
        lines.append("")
    if job:
        lines.append(
            f"job goodput: {job.get('goodput_pct', 0)}% "
            f"({job.get('productive_s', 0)}s productive / "
            f"{job.get('wall_s', 0)}s wall, "
            f"{job.get('relaunch_downtime_s', 0)}s relaunch downtime)")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="goodput_report")
    parser.add_argument("path", help="history dir (or app dir above it)")
    parser.add_argument("--json", action="store_true",
                        help="dump the raw goodput dict instead of the "
                             "table")
    args = parser.parse_args(argv)
    hist = find_history_dir(args.path)
    goodput = read_goodput_file(hist)
    if not goodput:
        print(f"no {C.GOODPUT_FILE} under {args.path}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(goodput, indent=1, sort_keys=True))
    else:
        print(format_report(goodput))
    return 0


if __name__ == "__main__":
    sys.exit(main())
