"""Serving benchmark: open-loop synthetic request stream vs the engine.

Open-loop (arrivals happen on schedule whether or not the server keeps
up — the honest way to measure a serving system; closed-loop clients
self-throttle and hide queueing collapse). A deterministic seeded stream
of requests is fired at the continuous-batching engine on the CPU backend
and ONE driver-parseable JSON line is printed, carrying the serving
headline metrics next to bench.py's training MFU:

  {"metric": "serve_tokens_per_sec", "value": ..., "unit": "tok/s",
   "tokens_per_sec": ..., "ttft_p50_s": ..., "ttft_p95_s": ...,
   "queue_depth_max": ..., "slot_occupancy_pct": ...,
   "scraped_metrics": {...}, ...}

After the load finishes, the bench also stands up the HTTP frontend and
scrapes `/v1/metrics` (Prometheus text exposition) so the JSON line
carries the engine-side TTFT/occupancy exactly as a dashboard would see
them — drift between the bench's own accounting and the scrape is a bug.

Run: python tools/serve_bench.py [--requests N] [--rate R] [--slots S]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")   # bench contract: CPU
os.environ.pop("PALLAS_AXON_POOL_IPS", None)    # never claim the tunnel
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="tiny")
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--rate", type=float, default=20.0,
                        help="open-loop arrival rate (req/s)")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--token-budget", type=int, default=64)
    parser.add_argument("--queue-depth", type=int, default=64)
    parser.add_argument("--max-new", type=int, default=12)
    parser.add_argument("--prompt-len", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args()

    import urllib.request

    import numpy as np
    import jax

    from tony_tpu.models.llama import get_config, llama_init
    from tony_tpu.serve.engine import (
        ContinuousBatchingEngine, QueueFullError,
        _percentile,
    )
    from tony_tpu.serve.frontend import ServeFrontend

    config = get_config(args.config)
    params = llama_init(config, jax.random.PRNGKey(0))
    engine = ContinuousBatchingEngine(
        params, config, n_slots=args.slots,
        token_budget=min(args.token_budget, config.max_seq),
        queue_depth=args.queue_depth)

    rng = np.random.RandomState(args.seed)
    prompts = [[int(t) for t in rng.randint(0, config.vocab_size,
                                            size=args.prompt_len)]
               for _ in range(args.requests)]

    # warmup outside the measurement: the one-time prefill/decode compiles
    # are a property of bring-up, not of steady-state serving
    engine.start()
    engine.submit(prompts[0], 2).result(timeout=300)

    t0 = time.monotonic()
    handles, shed = [], 0
    interval = 1.0 / args.rate if args.rate > 0 else 0.0
    for i, prompt in enumerate(prompts):
        target = t0 + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)       # open loop: late arrivals NEVER wait
        try:
            handles.append(engine.submit(prompt, args.max_new))
        except QueueFullError:
            shed += 1               # 429-equivalent: shed, keep the clock
    for h in handles:
        h.result(timeout=300)
    elapsed = time.monotonic() - t0

    # engine-side view over the real scrape path: stand the HTTP frontend
    # up and read /v1/metrics as a Prometheus scraper would — the bench
    # then reports the same numbers an operator's dashboard shows
    scraped = {}
    frontend = ServeFrontend(engine, port=0, host="127.0.0.1")
    frontend.start()
    try:
        from tony_tpu.observability import prometheus as prom
        with urllib.request.urlopen(
                f"http://127.0.0.1:{frontend.port}/v1/metrics"
                f"?format=prometheus", timeout=10) as resp:
            parsed = prom.parse(resp.read().decode("utf-8"))
        for key in ("ttft_p50_s", "ttft_p95_s", "slot_occupancy_pct",
                    "tokens_per_sec", "queue_depth_max",
                    "requests_submitted", "requests_rejected"):
            try:
                value = prom.get_sample(parsed, f"tony_serving_{key}")
            except KeyError:
                continue
            if value == value:          # skip NaN (no-traffic gauges)
                scraped[key] = round(value, 4)
    except Exception as e:  # noqa: BLE001 — the scrape must not fail the bench
        scraped = {"error": str(e)}
    finally:
        frontend.stop()
    engine.stop()

    ttfts = [h.ttft_s for h in handles if h.ttft_s is not None]
    total_tokens = sum(len(h.tokens) for h in handles)
    snap = engine.snapshot()
    tokens_per_sec = round(total_tokens / elapsed, 1)
    result = {
        "metric": "serve_tokens_per_sec",
        "value": tokens_per_sec,
        "unit": "tok/s",
        "tokens_per_sec": tokens_per_sec,
        "ttft_p50_s": round(_percentile(ttfts, 0.50), 4),
        "ttft_p95_s": round(_percentile(ttfts, 0.95), 4),
        "queue_depth_max": snap["queue_depth_max"],
        "slot_occupancy_pct": round(snap["slot_occupancy_pct"], 2),
        "itl_p50_ms": (round(snap["itl_p50_ms"], 3)
                       if snap.get("itl_p50_ms") is not None else None),
        # per-phase latency breakdown (queue_wait / prefill / per-token
        # decode, p50/p95/p99) so BENCH trajectories capture serving
        # latency COMPOSITION, not just the TTFT headline
        **{key: (round(snap[key], 5) if snap.get(key) is not None
                 else None)
           for key in (f"{phase}_{tag}"
                       for phase in ("queue_wait_s", "prefill_s",
                                     "decode_ms_per_token")
                       for tag in ("p50", "p95", "p99"))},
        # engine-side gauges as read off the /v1/metrics scrape
        "scraped_metrics": scraped,
        "requests": len(handles),
        "requests_shed": shed,
        "open_loop_rate_rps": args.rate,
        "slots": args.slots,
        "token_budget": engine.token_budget,
        "max_new": args.max_new,
        "model": args.config,
        "elapsed_s": round(elapsed, 2),
    }
    print(json.dumps(result, separators=(",", ":")), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
