"""Serving benchmark: open-loop synthetic request stream vs the engine.

Open-loop (arrivals happen on schedule whether or not the server keeps
up — the honest way to measure a serving system; closed-loop clients
self-throttle and hide queueing collapse). A deterministic seeded stream
of requests is fired at the continuous-batching engine on the CPU backend
and ONE driver-parseable JSON line is printed, carrying the serving
headline metrics next to bench.py's training MFU:

  {"metric": "serve_tokens_per_sec", "value": ..., "unit": "tok/s",
   "tokens_per_sec": ..., "ttft_p50_s": ..., "ttft_p95_s": ...,
   "queue_depth_max": ..., "slot_occupancy_pct": ...,
   "scraped_metrics": {...}, ...}

After the load finishes, the bench also stands up the HTTP frontend and
scrapes `/v1/metrics` (Prometheus text exposition) so the JSON line
carries the engine-side TTFT/occupancy exactly as a dashboard would see
them — drift between the bench's own accounting and the scrape is a bug.

**Fleet mode** (``--fleet``): the scaling story. For each replica count
in ``--fleet-replicas`` (default 1,2,4), N engine+frontend replicas come
up behind the fleet router (serve/router.py) and the SAME per-replica
offered load is fired at the router over HTTP (streamed, so TTFT is
measured through the real passthrough path). The line reports aggregate
tokens/sec and TTFT/ITL tails vs replica count plus the scaling ratios,
and the max-replica headlines are appended to tools/bench_history.jsonl
as ``serving_fleet_tokens_per_sec`` (tok/s, higher-is-better) and
``serving_fleet_ttft_p95_s`` (s, lower-is-better) under
tools/bench_compare.py gating — near-linear tokens/sec scaling with a
p95 TTFT no worse than single-instance at equal per-replica load is the
acceptance bar.

**Prefix-reuse mode** (``--prefix-reuse``): the paged-KV story. The
SAME seeded shared-system-prompt workload (``--reuse-ratio`` of
requests lead with one shared prefix) is fired at an OFF-baseline
replica and then an ON-candidate replica (``--prefix-sharing on``) in
one invocation. Headlines ``serving_prefix_tokens_per_sec`` (tok/s,
higher-is-better) and ``serving_prefix_ttft_p95_s`` (s,
lower-is-better) are appended to the trajectory ONLY when ON strictly
beats OFF on both — and every line carries the replica's scraped KV
hit rate, because a prefix "win" at 0% hit rate is noise.

Run: python tools/serve_bench.py [--requests N] [--rate R] [--slots S]
     [--fleet [--fleet-replicas 1,2,4]] [--prefix-reuse]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")   # bench contract: CPU
os.environ.pop("PALLAS_AXON_POOL_IPS", None)    # never claim the tunnel
os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)

_TOOLS_DIR = os.path.dirname(os.path.abspath(__file__))
# env-overridable so harnesses (and the contract tests) can redirect the
# append away from the checked-in trajectory file — same contract as
# bench.py's _append_history
HISTORY_PATH = os.environ.get(
    "TONY_BENCH_HISTORY_PATH",
    os.path.join(_TOOLS_DIR, "bench_history.jsonl"))


def _commit_stamp() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, timeout=10, cwd=_TOOLS_DIR).stdout.strip() \
            or "unknown"
    except Exception:  # noqa: BLE001 — metadata only
        return "unknown"


def append_history(entry: dict) -> None:
    """One commit+time-stamped headline into the bench trajectory
    (bench_compare judges the latest against the best same-backend
    prior). Mirrors bench.py's contract; pinned by the fleet
    append→compare contract test."""
    entry = dict(entry)
    entry.setdefault("measured_at",
                     time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
    entry.setdefault("commit", _commit_stamp())
    entry.setdefault("backend", "cpu")
    # same self-description floor as bench.py's _emit: not a fallback —
    # the serving bench is cpu-by-contract
    entry.setdefault("tpu_unavailable_reason",
                     "not-applicable: serving bench (cpu by contract)")
    try:
        with open(HISTORY_PATH, "a", encoding="utf-8") as f:
            f.write(json.dumps(entry, separators=(",", ":")) + "\n")
    except Exception:  # noqa: BLE001 — history is metadata, never fatal
        pass


def _percentile(samples, q):
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


# ---------------------------------------------------------------------------
# fleet mode
# ---------------------------------------------------------------------------

class _StreamResult:
    __slots__ = ("ttft_s", "tokens", "itl_s", "error")

    def __init__(self):
        self.ttft_s = None
        self.tokens = 0
        self.itl_s = []
        self.error = None


def _stream_request(base_url: str, prompt, max_new: int,
                    out: _StreamResult) -> None:
    """One streamed /v1/generate through the router: TTFT is the first
    token LINE's arrival (the real passthrough path, chunk flushing
    included), ITL the gaps between the rest."""
    t0 = time.monotonic()
    body = json.dumps({"prompt": prompt, "max_new_tokens": max_new,
                       "stream": True}).encode()
    req = urllib.request.Request(base_url + "/v1/generate", data=body,
                                 headers={"Content-Type":
                                          "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            last = None
            for line in resp:
                rec = json.loads(line)
                if "token" in rec:
                    now = time.monotonic()
                    if out.ttft_s is None:
                        out.ttft_s = now - t0
                    elif last is not None:
                        out.itl_s.append(now - last)
                    last = now
                    out.tokens += 1
    except Exception as e:  # noqa: BLE001 — shed/error both recorded
        out.error = f"{type(e).__name__}: {e}"


def _await_marker(proc, marker: str, deadline_s: float) -> str:
    """Bounded wait for a child's stdout bring-up marker line. A plain
    readline() would block past any deadline check on a silently wedged
    child; select keeps the deadline real, and the wedged child is
    KILLED before raising — an orphan replica/router spin-probing in
    the background poisons every later measurement on the box."""
    import select
    deadline = time.monotonic() + deadline_s
    buf = ""
    while time.monotonic() < deadline:
        ready, _, _ = select.select([proc.stdout], [], [],
                                    min(1.0, deadline - time.monotonic()))
        if not ready:
            continue
        chunk = proc.stdout.readline()
        if not chunk:
            raise RuntimeError(
                f"{marker} child died during bring-up (rc={proc.poll()})")
        buf = chunk
        if buf.startswith(marker + " "):
            return buf.split(None, 1)[1].strip()
    proc.kill()
    raise RuntimeError(f"child never printed {marker}")


def _spawn_replica(args, config, register=None,
                   extra_flags=()) -> "tuple":
    """One REAL serving replica: `python -m tony_tpu.serve` in its own
    process (own interpreter, own GIL, own engine thread) — the fleet's
    production shape, so the scaling numbers measure replicas, not N
    engines time-slicing one Python process. `register(proc)` is called
    the moment the child exists (before any waiting), so the caller can
    kill it on ANY failure path. `extra_flags` appends serve-CLI flags
    (the prefix-reuse leg turns the paged KV pool on/off with them).
    Returns (proc, url) once the child prints its SERVING_UP marker."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"                  # bench contract: CPU
    env.pop("PALLAS_AXON_POOL_IPS", None)         # never claim the tunnel
    env.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    env.pop("TONY_CONF_PATH", None)               # hermetic: flags only
    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.serve",
         "--config", args.config, "--port", "0", "--host", "127.0.0.1",
         "--slots", str(args.slots),
         "--token-budget", str(min(args.token_budget, config.max_seq)),
         "--queue-depth", str(args.queue_depth),
         *extra_flags],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=os.path.dirname(_TOOLS_DIR))
    if register is not None:
        register(proc)
    return proc, _await_marker(proc, "SERVING_UP", 180.0)


def _stop_replicas(procs) -> None:
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()      # SIGTERM -> drain path -> clean exit
    for proc in procs:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


def _run_fleet_point(config, args, n_replicas: int) -> dict:
    """One sweep point: n subprocess replicas behind the router, equal
    PER-REPLICA offered load (rate*n req/s, requests*n total)."""
    import numpy as np

    spawned: list = [None] * n_replicas
    launched: list = []             # every child, marker seen or not

    def bring_up(i):
        spawned[i] = _spawn_replica(args, config, register=launched.append)

    threads = [threading.Thread(target=bring_up, args=(i,), daemon=True)
               for i in range(n_replicas)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=240)
    if any(s is None for s in spawned):
        _stop_replicas(launched)
        raise RuntimeError("fleet bring-up timed out")
    procs = [p for p, _ in spawned]
    urls = [u for _, u in spawned]
    # the router is its own process too (the production shape — and the
    # bench parent's client threads must not share a GIL with the relay
    # path, or the measured TTFT tail is the parent's, not the fleet's)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    rproc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cli", "router",
         "--endpoints", ",".join(urls), "--port", "0",
         "--host", "127.0.0.1",
         "--probe-ttl-ms", str(args.probe_ttl_ms),
         "--spillover-retries", str(max(1, n_replicas - 1))],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env, cwd=os.path.dirname(_TOOLS_DIR))
    try:
        base = _await_marker(rproc, "ROUTER_UP", 60.0)
    except Exception:
        _stop_replicas(procs + [rproc])
        raise

    # from here the child fleet MUST die on every exit path — an
    # orphaned router spin-probing dead replicas is exactly the kind of
    # background load that poisons the next run's tail latencies
    try:
        rng = np.random.RandomState(args.seed)
        total = args.requests * n_replicas
        prompts = [[int(t) for t in rng.randint(0, config.vocab_size,
                                                size=args.prompt_len)]
                   for _ in range(total)]
        # warmup outside the measurement (compile is bring-up, not
        # serving): every replica pays its own admission+decode compile
        # — one direct request each, in parallel, at the measured
        # prompt length
        warms = [_StreamResult() for _ in urls]
        warm_threads = [
            threading.Thread(target=_stream_request,
                             args=(url, prompts[0], args.max_new, w),
                             daemon=True)
            for url, w in zip(urls, warms)]
        for th in warm_threads:
            th.start()
        for th in warm_threads:
            th.join(timeout=240)
        if any(w.error for w in warms):
            raise RuntimeError(
                f"fleet warmup failed: {[w.error for w in warms]}")

        rate = args.rate * n_replicas
        rounds = []
        for i in range(max(1, args.fleet_rounds)):
            rounds.append(_measure_window(base, prompts, rate, args))
            print(f"[serve_bench]   round {i + 1}: "
                  f"{rounds[-1]['tokens_per_sec']} tok/s ttft_p95 "
                  f"{rounds[-1]['ttft_p95_s']}s "
                  f"errors {rounds[-1]['requests_errored']}",
                  file=sys.stderr, flush=True)
        # best round by TTFT tail (same discipline as bench.py's retry
        # ladder): a shared CI host lands multi-hundred-ms scheduler
        # stalls that poison every sample in flight at once, so a
        # stalled window measures the HOST, not the fleet — the
        # cleanest round is the fleet's capability at this load.
        # Throughput barely varies across rounds (open-loop offered
        # load); the tail is what a stall hits. A round with errors
        # (or no completed requests — its tail renders as a bogus 0.0)
        # can never outrank a clean one.
        for p in rounds:
            p.pop("_ttfts")
            p.pop("_itls")
        point = min(rounds,
                    key=lambda p: (p["requests_ok"] == 0,
                                   p["requests_errored"],
                                   p["ttft_p95_s"]))
        point["rounds"] = len(rounds)

        with urllib.request.urlopen(base + "/v1/fleet", timeout=10) as r:
            stats = json.loads(r.read().decode("utf-8"))["stats"]
    finally:
        _stop_replicas(procs + [rproc])
    point["replicas"] = n_replicas
    point["router_stats"] = stats
    return point


def _measure_window(base: str, prompts: list, rate: float, args) -> dict:
    """One measured open-loop window at `rate` req/s total. Client
    threads are pre-spawned and sleep to their arrival slot — thread
    creation never rides the arrival path, so the measured TTFT is the
    fleet's, not the load generator's."""
    interval = 1.0 / rate if rate > 0 else 0.0
    total = len(prompts)
    results = [_StreamResult() for _ in range(total)]
    start = threading.Event()
    t0_box = [0.0]

    def fire(i):
        start.wait(timeout=60)
        delay = t0_box[0] + i * interval - time.monotonic()
        if delay > 0:
            time.sleep(delay)       # open loop: late arrivals NEVER wait
        _stream_request(base, prompts[i], args.max_new, results[i])

    threads = [threading.Thread(target=fire, args=(i,), daemon=True)
               for i in range(total)]
    for th in threads:
        th.start()
    t0_box[0] = time.monotonic()
    start.set()
    for th in threads:
        th.join(timeout=300)
    elapsed = time.monotonic() - t0_box[0]

    ok = [r for r in results if r.error is None and r.ttft_s is not None]
    shed = sum(1 for r in results if r.error is not None)
    ttfts = [r.ttft_s for r in ok]
    itls = [s for r in ok for s in r.itl_s]
    total_tokens = sum(r.tokens for r in ok)
    return {
        "tokens_per_sec": round(total_tokens / max(elapsed, 1e-9), 1),
        "ttft_p50_s": round(_percentile(ttfts, 0.50) or 0.0, 4),
        "ttft_p95_s": round(_percentile(ttfts, 0.95) or 0.0, 4),
        "itl_p50_ms": round(1000 * (_percentile(itls, 0.50) or 0.0), 3),
        "itl_p95_ms": round(1000 * (_percentile(itls, 0.95) or 0.0), 3),
        "requests_ok": len(ok),
        "requests_errored": shed,
        "offered_rate_rps": rate,
        "elapsed_s": round(elapsed, 2),
        "_ttfts": ttfts,        # raw samples: popped by the rounds
        "_itls": itls,          # aggregation, never emitted
    }


# ---------------------------------------------------------------------------
# prefix-reuse mode
# ---------------------------------------------------------------------------

def _scrape_kv_metrics(base_url: str) -> dict:
    """Read the replica's paged-KV counters off /v1/metrics exactly as a
    dashboard scraper would — the bench's hit-rate disclosure and the
    operator's graph must be the same number."""
    from tony_tpu.observability import prometheus as prom
    out = {}
    try:
        with urllib.request.urlopen(
                base_url + "/v1/metrics?format=prometheus",
                timeout=10) as resp:
            parsed = prom.parse(resp.read().decode("utf-8"))
        for key in ("kv_hit_rate_pct", "kv_hit_total", "kv_miss_total",
                    "kv_evict_total", "kv_occupancy_pct"):
            try:
                value = prom.get_sample(parsed, f"tony_serving_{key}")
            except KeyError:
                continue
            if value == value:          # skip NaN
                out[key] = round(value, 3)
    except Exception as e:  # noqa: BLE001 — disclosure, never fatal
        out["error"] = str(e)
    return out


def _prefix_prompts(config, args, rng) -> "tuple":
    """The reuse workload: one seeded shared system prompt; a
    `--reuse-ratio` fraction of requests lead with it (unique seeded
    suffix each), the rest are fully unique at the SAME total length —
    ON and OFF legs see byte-identical traffic, and equal lengths keep
    the suffix-prefill compile set to two shapes (full-length miss,
    post-match suffix), paid once in warmup."""
    shared = [int(t) for t in rng.randint(0, config.vocab_size,
                                          size=args.shared_prefix_len)]
    total_len = args.shared_prefix_len + args.prompt_len
    n_reuse = int(round(args.requests * args.reuse_ratio))
    prompts = []
    for i in range(args.requests):
        if i < n_reuse:
            suffix = rng.randint(0, config.vocab_size,
                                 size=args.prompt_len)
            prompts.append(shared + [int(t) for t in suffix])
        else:
            unique = rng.randint(0, config.vocab_size, size=total_len)
            prompts.append([int(t) for t in unique])
    # interleave reuse/unique deterministically so reuse traffic spreads
    # over the window instead of front-loading every hit
    order = rng.permutation(len(prompts))
    return [prompts[i] for i in order], shared


def _run_prefix_point(config, args, sharing: bool) -> dict:
    """One leg (pool ON or OFF): a single subprocess replica, the same
    seeded reuse workload, best-of-rounds window, KV counters scraped
    off /v1/metrics after the measurement."""
    import numpy as np

    flags = (("--prefix-sharing", "on",
              "--kv-page-size", str(args.kv_page_size),
              *(("--kv-pages", str(args.kv_pages))
                if args.kv_pages > 0 else ()))
             if sharing else ("--prefix-sharing", "off"))
    launched: list = []
    proc, base = _spawn_replica(args, config,
                                register=launched.append,
                                extra_flags=flags)
    try:
        rng = np.random.RandomState(args.seed)
        prompts, shared = _prefix_prompts(config, args, rng)
        # warmup pays every compile shape up front: a unique full-length
        # prompt (miss path), then the shared prefix twice — the first
        # seals its pages, the second takes the hit path and compiles
        # the short-suffix prefill shape
        total_len = args.shared_prefix_len + args.prompt_len
        warm_rng = np.random.RandomState(args.seed + 7919)
        warm_unique = [int(t) for t in warm_rng.randint(
            0, config.vocab_size, size=total_len)]
        warm_shared = shared + [int(t) for t in warm_rng.randint(
            0, config.vocab_size, size=args.prompt_len)]
        for prompt in (warm_unique, warm_shared, warm_shared):
            w = _StreamResult()
            _stream_request(base, prompt, args.max_new, w)
            if w.error:
                raise RuntimeError(f"prefix warmup failed: {w.error}")
        rounds = []
        for i in range(max(1, args.fleet_rounds)):
            rounds.append(_measure_window(base, prompts, args.rate,
                                          args))
            kv = _scrape_kv_metrics(base)
            print(f"[serve_bench]   {'ON ' if sharing else 'OFF'} "
                  f"round {i + 1}: "
                  f"{rounds[-1]['tokens_per_sec']} tok/s ttft_p95 "
                  f"{rounds[-1]['ttft_p95_s']}s "
                  f"errors {rounds[-1]['requests_errored']} "
                  f"kv_hit_rate "
                  f"{kv.get('kv_hit_rate_pct', 0.0)}%",
                  file=sys.stderr, flush=True)
        for p in rounds:
            p.pop("_ttfts")
            p.pop("_itls")
        point = min(rounds,
                    key=lambda p: (p["requests_ok"] == 0,
                                   p["requests_errored"],
                                   p["ttft_p95_s"]))
        point["rounds"] = len(rounds)
        point.update(_scrape_kv_metrics(base))
    finally:
        _stop_replicas(launched)
    point["prefix_sharing"] = sharing
    return point


def ttft_attribution(ttft_s, queue_wait_s=None, prefill_s=None,
                     route_ms=0.0, migrate_ms=0.0) -> dict:
    """Pure TTFT-attribution disclosure for one bench line (pinned by
    the bench contract tests): where the p95 first-token time went, in
    ms, under the canonical component order (observability/reqtrace
    COMPONENTS). Sum-consistent BY CONSTRUCTION: the components plus
    ``ttft_attr_unattributed_ms`` always total ``ttft_attr_total_ms``
    exactly — decode is the first-token remainder after queue+prefill+
    migrate when those phases were measured, and anything the bench
    could not observe (e.g. per-replica phases behind a router) lands
    in the unattributed bucket instead of being invented."""
    route = max(0.0, float(route_ms or 0.0))
    ttft_ms = 1000.0 * float(ttft_s or 0.0)
    total = route + ttft_ms
    migrate = max(0.0, float(migrate_ms or 0.0))
    queue = 1000.0 * float(queue_wait_s) if queue_wait_s is not None \
        else 0.0
    prefill = 1000.0 * float(prefill_s) if prefill_s is not None else 0.0
    if queue_wait_s is not None and prefill_s is not None:
        decode = max(0.0, ttft_ms - queue - prefill - migrate)
    else:
        decode = 0.0
    unattributed = total - route - queue - prefill - migrate - decode
    out = {"ttft_attr_route_ms": route,
           "ttft_attr_queue_ms": queue,
           "ttft_attr_prefill_ms": prefill,
           "ttft_attr_migrate_ms": migrate,
           "ttft_attr_decode_ms": decode,
           "ttft_attr_unattributed_ms": unattributed,
           "ttft_attr_total_ms": total}
    # one rounding pass, remainder-corrected so the rounded values STILL
    # sum exactly (the contract test checks the emitted numbers)
    rounded = {k: round(v, 3) for k, v in out.items()}
    drift = rounded["ttft_attr_total_ms"] - sum(
        v for k, v in rounded.items() if k != "ttft_attr_total_ms")
    rounded["ttft_attr_unattributed_ms"] = round(
        rounded["ttft_attr_unattributed_ms"] + drift, 3)
    return rounded


def build_prefix_history_entries(on: dict, off: dict, model: str,
                                 reuse_ratio: float) -> list:
    """Gate + build the prefix-reuse trajectory entries (pure — pinned
    by the bench contract tests). Returns [] unless the ON leg strictly
    beats the OFF leg on BOTH headlines with non-degenerate
    measurements: appending a losing or zero-valued run would poison
    the bench_compare baseline for every later commit. Every entry
    carries the KV hit-rate disclosure next to the number it
    justifies."""
    on_tps = float(on.get("tokens_per_sec") or 0)
    off_tps = float(off.get("tokens_per_sec") or 0)
    on_ttft = float(on.get("ttft_p95_s") or 0)
    off_ttft = float(off.get("ttft_p95_s") or 0)
    if min(on_tps, off_tps, on_ttft, off_ttft) <= 0:
        return []
    if on.get("requests_errored") or off.get("requests_errored"):
        return []
    if not (on_tps > off_tps and on_ttft < off_ttft):
        return []
    disclosure = {
        "model": model,
        "reuse_ratio": round(float(reuse_ratio), 3),
        "kv_hit_rate_pct": float(on.get("kv_hit_rate_pct", 0.0) or 0.0),
        "baseline_tokens_per_sec": off_tps,
        "baseline_ttft_p95_s": off_ttft,
    }
    return [
        {"metric": "serving_prefix_tokens_per_sec", "value": on_tps,
         "unit": "tok/s", **disclosure},
        {"metric": "serving_prefix_ttft_p95_s", "value": on_ttft,
         "unit": "s", **disclosure},
    ]


def run_prefix_reuse(args) -> int:
    """The --prefix-reuse leg: OFF-baseline then ON-candidate, same
    replica shape, same seeded shared-system-prompt workload. The two
    headlines land in bench_history.jsonl ONLY when ON strictly wins
    both (build_prefix_history_entries gates), and the KV hit rate is
    disclosed on every line — a prefix win at 0% hit rate is noise, not
    a result."""
    import signal

    from tony_tpu.models.llama import get_config

    def _term(signum, frame):
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _term)
    config = get_config(args.config)
    off = _run_prefix_point(config, args, sharing=False)
    print(f"[serve_bench] prefix OFF: {off['tokens_per_sec']} tok/s, "
          f"ttft_p95 {off['ttft_p95_s']}s", file=sys.stderr, flush=True)
    on = _run_prefix_point(config, args, sharing=True)
    print(f"[serve_bench] prefix ON:  {on['tokens_per_sec']} tok/s, "
          f"ttft_p95 {on['ttft_p95_s']}s, kv_hit_rate "
          f"{on.get('kv_hit_rate_pct', 0.0)}%",
          file=sys.stderr, flush=True)
    entries = build_prefix_history_entries(on, off, args.config,
                                           args.reuse_ratio)
    for entry in entries:
        append_history(entry)
    if not entries:
        print("[serve_bench] prefix-reuse: ON did not strictly beat "
              "OFF on both headlines — nothing appended",
              file=sys.stderr, flush=True)
    result = {
        "metric": "serving_prefix_tokens_per_sec",
        "value": on["tokens_per_sec"],
        "unit": "tok/s",
        "backend": "cpu",
        "ttft_p95_s": on["ttft_p95_s"],
        "kv_hit_rate_pct": float(on.get("kv_hit_rate_pct", 0.0) or 0.0),
        "reuse_ratio": args.reuse_ratio,
        "shared_prefix_len": args.shared_prefix_len,
        "kv_page_size": args.kv_page_size,
        "appended": len(entries),
        "on": on, "off": off,
        "slots": args.slots,
        "rate_rps": args.rate,
        "requests": args.requests,
        "max_new": args.max_new,
        "model": args.config,
    }
    result.update(ttft_attribution(on["ttft_p95_s"]))
    print(json.dumps(result, separators=(",", ":")), flush=True)
    return 0


def run_fleet(args) -> int:
    import signal

    from tony_tpu.models.llama import get_config

    # a harness deadline (timeout(1) SIGTERM) must still unwind the
    # try/finally that stops the child fleet — orphaned replicas/router
    # poison every later measurement on the box
    def _term(signum, frame):
        raise SystemExit(143)

    signal.signal(signal.SIGTERM, _term)
    config = get_config(args.config)
    counts = [int(c) for c in args.fleet_replicas.split(",") if c]
    points = {}
    for n in counts:
        points[n] = _run_fleet_point(config, args, n)
        print(f"[serve_bench] fleet point replicas={n}: "
              f"{points[n]['tokens_per_sec']} tok/s, ttft_p95 "
              f"{points[n]['ttft_p95_s']}s", file=sys.stderr, flush=True)
    # honest ratio labeling: "vs 1 replica" only when 1 was actually
    # measured; a 2,4-only sweep reports vs its smallest point under a
    # key that says so, never a fabricated single-instance baseline
    base_n = 1 if 1 in points else min(points)
    base = points[base_n]
    head = points[max(counts)]
    scaling_key = "scaling_vs_1" if base_n == 1 \
        else f"scaling_vs_{base_n}"
    scaling = {
        str(n): round(p["tokens_per_sec"]
                      / max(base["tokens_per_sec"], 1e-9), 3)
        for n, p in points.items()}
    result = {
        "metric": "serving_fleet_tokens_per_sec",
        "value": head["tokens_per_sec"],
        "unit": "tok/s",
        "backend": "cpu",
        "replicas": max(counts),
        "ttft_p95_s": head["ttft_p95_s"],
        "itl_p95_ms": head["itl_p95_ms"],
        scaling_key: scaling,
        "scaling_base_replicas": base_n,
        "points": [points[n] for n in counts],
        "slots": args.slots,
        "rate_per_replica_rps": args.rate,
        "requests_per_replica": args.requests,
        "max_new": args.max_new,
        "model": args.config,
    }
    # client-side view only: per-replica queue/prefill phases are not
    # visible through the router, so they land in unattributed
    result.update(ttft_attribution(head["ttft_p95_s"]))
    # two gated trajectory entries: aggregate throughput (higher-is-
    # better) and the fleet TTFT tail (unit "s" → lower-is-better)
    append_history({
        "metric": "serving_fleet_tokens_per_sec",
        "value": head["tokens_per_sec"], "unit": "tok/s",
        "replicas": max(counts), scaling_key: scaling,
        "scaling_base_replicas": base_n,
        "model": args.config})
    append_history({
        "metric": "serving_fleet_ttft_p95_s",
        "value": head["ttft_p95_s"], "unit": "s",
        "replicas": max(counts), "model": args.config})
    print(json.dumps(result, separators=(",", ":")), flush=True)
    return 0


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="tiny")
    parser.add_argument("--requests", type=int, default=24)
    parser.add_argument("--rate", type=float, default=None,
                        help="open-loop arrival rate (req/s; per replica "
                             "in --fleet mode). Default 20, or 12 in "
                             "fleet mode — the fleet default keeps the "
                             "widest sweep point inside a 2-core CI "
                             "host's capacity, so the sweep measures "
                             "replica scaling, not host "
                             "oversubscription")
    parser.add_argument("--slots", type=int, default=4)
    parser.add_argument("--token-budget", type=int, default=64)
    parser.add_argument("--queue-depth", type=int, default=64)
    parser.add_argument("--max-new", type=int, default=12)
    parser.add_argument("--prompt-len", type=int, default=8)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--fleet", action="store_true",
                        help="fleet mode: replica sweep behind the "
                             "router, scaling headlines into "
                             "bench_history.jsonl")
    parser.add_argument("--fleet-replicas", default="1,2,4",
                        help="comma-separated replica counts to sweep")
    parser.add_argument("--fleet-rounds", type=int, default=3,
                        help="measured windows per sweep point; the "
                             "best clean round (fewest errors, then "
                             "lowest ttft_p95) is reported")
    parser.add_argument("--probe-ttl-ms", type=int, default=100,
                        help="router load-probe cache TTL in fleet mode")
    parser.add_argument("--prefix-reuse", action="store_true",
                        help="prefix-reuse mode: paged-KV OFF baseline "
                             "vs ON candidate over shared-system-prompt "
                             "traffic; winning runs append "
                             "serving_prefix_* headlines")
    parser.add_argument("--reuse-ratio", type=float, default=0.6,
                        help="fraction of requests leading with the "
                             "shared system prompt")
    parser.add_argument("--shared-prefix-len", type=int, default=32,
                        help="shared system-prompt length in tokens "
                             "(page-aligned for full reuse)")
    parser.add_argument("--kv-page-size", type=int, default=16,
                        help="KV page size for the ON leg")
    parser.add_argument("--kv-pages", type=int, default=0,
                        help="KV pool size for the ON leg (0 = the "
                             "engine's slots-scaled default)")
    args = parser.parse_args()
    if args.rate is None:
        args.rate = 12.0 if (args.fleet or args.prefix_reuse) else 20.0

    if args.prefix_reuse:
        return run_prefix_reuse(args)
    if args.fleet:
        return run_fleet(args)

    import urllib.request

    import numpy as np
    import jax

    from tony_tpu.models.llama import get_config, llama_init
    from tony_tpu.serve.engine import (
        ContinuousBatchingEngine, QueueFullError,
    )
    from tony_tpu.serve.frontend import ServeFrontend

    config = get_config(args.config)
    params = llama_init(config, jax.random.PRNGKey(0))
    engine = ContinuousBatchingEngine(
        params, config, n_slots=args.slots,
        token_budget=min(args.token_budget, config.max_seq),
        queue_depth=args.queue_depth)

    rng = np.random.RandomState(args.seed)
    prompts = [[int(t) for t in rng.randint(0, config.vocab_size,
                                            size=args.prompt_len)]
               for _ in range(args.requests)]

    # warmup outside the measurement: the one-time prefill/decode compiles
    # are a property of bring-up, not of steady-state serving
    engine.start()
    engine.submit(prompts[0], 2).result(timeout=300)

    t0 = time.monotonic()
    handles, shed = [], 0
    interval = 1.0 / args.rate if args.rate > 0 else 0.0
    for i, prompt in enumerate(prompts):
        target = t0 + i * interval
        delay = target - time.monotonic()
        if delay > 0:
            time.sleep(delay)       # open loop: late arrivals NEVER wait
        try:
            handles.append(engine.submit(prompt, args.max_new))
        except QueueFullError:
            shed += 1               # 429-equivalent: shed, keep the clock
    for h in handles:
        h.result(timeout=300)
    elapsed = time.monotonic() - t0

    # engine-side view over the real scrape path: stand the HTTP frontend
    # up and read /v1/metrics as a Prometheus scraper would — the bench
    # then reports the same numbers an operator's dashboard shows
    scraped = {}
    frontend = ServeFrontend(engine, port=0, host="127.0.0.1")
    frontend.start()
    try:
        from tony_tpu.observability import prometheus as prom
        with urllib.request.urlopen(
                f"http://127.0.0.1:{frontend.port}/v1/metrics"
                f"?format=prometheus", timeout=10) as resp:
            parsed = prom.parse(resp.read().decode("utf-8"))
        for key in ("ttft_p50_s", "ttft_p95_s", "slot_occupancy_pct",
                    "tokens_per_sec", "queue_depth_max",
                    "requests_submitted", "requests_rejected"):
            try:
                value = prom.get_sample(parsed, f"tony_serving_{key}")
            except KeyError:
                continue
            if value == value:          # skip NaN (no-traffic gauges)
                scraped[key] = round(value, 4)
    except Exception as e:  # noqa: BLE001 — the scrape must not fail the bench
        scraped = {"error": str(e)}
    finally:
        frontend.stop()
    engine.stop()

    ttfts = [h.ttft_s for h in handles if h.ttft_s is not None]
    total_tokens = sum(len(h.tokens) for h in handles)
    snap = engine.snapshot()
    tokens_per_sec = round(total_tokens / elapsed, 1)
    result = {
        "metric": "serve_tokens_per_sec",
        "value": tokens_per_sec,
        "unit": "tok/s",
        "tokens_per_sec": tokens_per_sec,
        "ttft_p50_s": round(_percentile(ttfts, 0.50), 4),
        "ttft_p95_s": round(_percentile(ttfts, 0.95), 4),
        "queue_depth_max": snap["queue_depth_max"],
        "slot_occupancy_pct": round(snap["slot_occupancy_pct"], 2),
        "itl_p50_ms": (round(snap["itl_p50_ms"], 3)
                       if snap.get("itl_p50_ms") is not None else None),
        # per-phase latency breakdown (queue_wait / prefill / per-token
        # decode, p50/p95/p99) so BENCH trajectories capture serving
        # latency COMPOSITION, not just the TTFT headline
        **{key: (round(snap[key], 5) if snap.get(key) is not None
                 else None)
           for key in (f"{phase}_{tag}"
                       for phase in ("queue_wait_s", "prefill_s",
                                     "decode_ms_per_token")
                       for tag in ("p50", "p95", "p99"))},
        # engine-side gauges as read off the /v1/metrics scrape
        "scraped_metrics": scraped,
        "requests": len(handles),
        "requests_shed": shed,
        "open_loop_rate_rps": args.rate,
        "slots": args.slots,
        "token_budget": engine.token_budget,
        "max_new": args.max_new,
        "model": args.config,
        "elapsed_s": round(elapsed, 2),
    }
    result.update(ttft_attribution(result["ttft_p95_s"],
                                   snap.get("queue_wait_s_p95"),
                                   snap.get("prefill_s_p95")))
    print(json.dumps(result, separators=(",", ":")), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
