"""AOT-compile a Llama train step (any preset, --model) against a
detached TPU topology (VERDICT r3 weak #4 / next-round item 3).

JAX's AOT path (`jax.experimental.topologies.get_topology_desc` +
`jit(...).lower(...).compile()`) runs the REAL XLA:TPU compiler against a
TopologyDescription without any attached device, so the per-chip HBM plan
in docs/SCALING.md can be validated by the compiler instead of
arithmetic. Prints one JSON summary and writes tools/aot_8b_result.json.

Usage (CPU host, no TPU needed):
    env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE \
        JAX_PLATFORMS=cpu python tools/aot_8b.py [--mesh fsdp=16]
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

GiB = 1024 ** 3
# SCALING.md "Recommended configuration": batch 16 x seq 8192 on
# fsdp=16 over a v5p-32 slice (16 chips, 95 GB HBM each)
BATCH, SEQ = 16, 8192
TOPOLOGY = "v5p:2x2x4"
HBM_GIB = {"v5p": 95.0, "v5e": 16.0, "v5lite": 16.0, "v4": 32.0}


def main() -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description="AOT-compile a Llama train step (--model preset) "
                    "against a detached TPU topology")
    parser.add_argument("--mesh", default="fsdp:16",
                        help="axis:size list, e.g. fsdp:8,tp:2 or "
                             "pp:4,fsdp:4")
    parser.add_argument("--topology", default=TOPOLOGY)
    parser.add_argument("--slices", type=int, default=1,
                        help=">1 compiles a multi-slice hybrid mesh "
                             "(outermost axes cross DCN)")
    parser.add_argument("--batch", type=int, default=BATCH)
    parser.add_argument("--seq", type=int, default=SEQ)
    parser.add_argument("--generate", action="store_true",
                        help="compile the inference path (prefill + "
                             "KV-cache decode scan) instead of the "
                             "train step")
    parser.add_argument("--virtual", type=int, default=1,
                        help="virtual chunks per pipeline stage (pp "
                             "meshes; >1 = interleaved schedule)")
    parser.add_argument("--model", default="llama3_8b",
                        help="LlamaConfig preset, or a MoEConfig preset "
                             "(moe_tiny / mixtral_proxy) for the "
                             "expert-parallel path")
    args = parser.parse_args()
    mesh_kwargs = {}
    for part in args.mesh.split(","):
        k, _, v = part.partition(":")
        mesh_kwargs[k.strip()] = int(v)
    if args.generate and (args.virtual > 1
                          or mesh_kwargs.get("pp", 1) > 1):
        # argv-detectable conflict: fail before any topology/mesh work
        raise SystemExit(
            "--generate compiles the inference path only; --virtual "
            "and pp meshes apply to the train step — drop them or "
            "drop --generate")
    topology, num_slices = args.topology, args.slices
    batch, seq = args.batch, args.seq
    # strict lookup: an unknown device generation must not inherit the
    # largest part's HBM and fake a fits=true verdict
    hbm_gib = next((v for k, v in HBM_GIB.items()
                    if topology.lower().startswith(k)), None)

    import jax
    import jax.numpy as jnp
    import optax
    from jax.experimental import topologies
    from jax.sharding import NamedSharding

    from tony_tpu.models.llama import (
        get_config, llama_init, llama_loss, llama_param_axes,
    )
    from tony_tpu.parallel.mesh import make_mesh, plan_mesh
    from tony_tpu.parallel.sharding import (
        logical_to_mesh_axes, make_partition_spec,
    )
    from tony_tpu.train.precision import with_f32_master
    from tony_tpu.train.step import make_train_step

    t0 = time.monotonic()
    kw = {"num_slices": num_slices} if num_slices > 1 else {}
    topo = topologies.get_topology_desc(topology, "tpu", **kw)
    if num_slices > 1:
        # DCN-crossing layout: outermost plan axes span slices, inner
        # axes stay within a slice on ICI (the scaling-book rule the
        # hybrid mesh implements)
        from tony_tpu.parallel.mesh import make_hybrid_mesh
        mesh = make_hybrid_mesh(plan_mesh(len(topo.devices),
                                          **mesh_kwargs), topo.devices)
    else:
        mesh = make_mesh(plan_mesh(len(topo.devices), **mesh_kwargs),
                         topo.devices)
    print(f"[aot] topology {topology} x{num_slices}: "
          f"{len(topo.devices)} chips, mesh {dict(mesh.shape)}",
          file=sys.stderr)

    from tony_tpu.models.moe import is_moe_preset
    is_moe = is_moe_preset(args.model)
    if is_moe:
        from tony_tpu.models.moe import (
            get_moe_config, moe_init, moe_loss, moe_param_axes,
        )
        config = get_moe_config(args.model)
        init_fn = partial(moe_init, config)
        param_axes = moe_param_axes(config)
    else:
        config = get_config(args.model)
        init_fn = partial(llama_init, config)
        param_axes = llama_param_axes(config)

    def sds(tree, spec_tree=None):
        """eval_shape tree -> ShapeDtypeStructs with shardings."""
        def one(leaf, spec=None):
            sharding = NamedSharding(
                mesh, spec if spec is not None else jax.P())
            return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                        sharding=sharding)
        if spec_tree is None:
            return jax.tree.map(one, tree)
        return jax.tree.map(one, tree, spec_tree)

    abstract_params = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    param_specs = make_partition_spec(param_axes, mesh=mesh)
    params_in = sds(abstract_params, param_specs)

    if args.generate:
        # inference path: --seq is the PROMPT length (prefill), 64 new
        # tokens decoded through the KV-cache scan
        if is_moe:
            raise SystemExit("--generate supports the Llama presets only")
        from tony_tpu.models.generate import generate
        prompt_in = jax.ShapeDtypeStruct(
            (batch, seq), jnp.int32,
            sharding=NamedSharding(
                mesh, logical_to_mesh_axes(("batch",), mesh=mesh)))
        print("[aot] lowering + compiling generate (prefill + KV-cache "
              "decode scan)...", file=sys.stderr)
        with jax.set_mesh(mesh):
            exe = jax.jit(
                lambda p, t: generate(p, config, t, 64)).lower(
                    params_in, prompt_in).compile()
    else:
        exe = None
    # train-step construction only when the train step is what compiles:
    # in --generate mode the full-scale optimizer eval_shape + loss/step
    # build was pure wasted compile-path work (r4 advisor)
    if exe is None:
        optimizer = with_f32_master(optax.adamw(3e-4))
        with jax.set_mesh(mesh):
            # explicit optimizer-state specs (masters/moments mirror the
            # param tree): propagation left the Adam moments replicated on
            # this very compile before opt_state_specs existed
            from tony_tpu.parallel.sharding import opt_state_specs
            opt_shapes = jax.eval_shape(optimizer.init, params_in)
            opt_in = jax.tree.map(
                lambda s, spec: jax.ShapeDtypeStruct(
                    s.shape, s.dtype, sharding=NamedSharding(mesh, spec)),
                opt_shapes, opt_state_specs(opt_shapes, param_specs))

            batch_spec = logical_to_mesh_axes(("batch", "seq"), mesh=mesh)
            if is_moe:
                # MoE batches ship as {'tokens': (B, S+1)}; seq+1 must stay
                # divisible enough for the sp spec -> keep tokens unsharded
                # on seq (moe runs ep/fsdp meshes)
                tok_spec = logical_to_mesh_axes(("batch",), mesh=mesh)
                batch_in = {"tokens": jax.ShapeDtypeStruct(
                    (batch, seq + 1), jnp.int32,
                    sharding=NamedSharding(mesh, tok_spec))}
            else:
                batch_in = {
                    "inputs": jax.ShapeDtypeStruct(
                        (batch, seq), jnp.int32,
                        sharding=NamedSharding(mesh, batch_spec)),
                    "targets": jax.ShapeDtypeStruct(
                        (batch, seq), jnp.int32,
                        sharding=NamedSharding(mesh, batch_spec)),
                }
            if is_moe:
                if mesh_kwargs.get("pp", 1) > 1:
                    raise SystemExit(
                        "MoE has no pipelined loss — a pp axis would record "
                        "a mesh the compiled program never uses")
                loss_fn = partial(moe_loss, config=config)
            elif mesh_kwargs.get("pp", 1) > 1:
                # pipeline-parallel compile check: the pp path (1F1B custom
                # backward, blockwise attention inside the manual stage,
                # interleaved when --virtual > 1) had only ever lowered for
                # CPU before this
                from tony_tpu.models.llama import llama_loss_pipelined
                loss_fn = partial(llama_loss_pipelined, config=config,
                                  mesh=mesh, n_micro=4,
                                  n_virtual=args.virtual)
            else:
                loss_fn = partial(llama_loss, config=config)
            step = make_train_step(loss_fn, optimizer, jit=False,
                                   emit_accum_dtype=True)
            print("[aot] lowering + compiling the full train step "
                  "(fwd+bwd+adamw, donated state)...", file=sys.stderr)
            exe = jax.jit(
                step, donate_argnums=(0, 1)).lower(
                    params_in, opt_in, batch_in).compile()

    mem = exe.memory_analysis()
    result = {
        "topology": topology,
        "num_slices": num_slices,
        "mesh": dict(mesh.shape),
        "model": args.model,
        **({"mode": "generate"} if args.generate else {}),
        **({"n_virtual": args.virtual} if args.virtual > 1 else {}),
        "batch": batch, "seq": seq,
        "compile_s": round(time.monotonic() - t0, 1),
    }
    if mem is not None:
        per_chip = {
            "argument_gib": round(mem.argument_size_in_bytes / GiB, 2),
            "output_gib": round(mem.output_size_in_bytes / GiB, 2),
            "temp_gib": round(mem.temp_size_in_bytes / GiB, 2),
            "peak_total_gib": round(
                (mem.argument_size_in_bytes + mem.temp_size_in_bytes)
                / GiB, 2),
            "hbm_per_chip_gib": hbm_gib,
        }
        per_chip["fits"] = (per_chip["peak_total_gib"] < hbm_gib
                            if hbm_gib is not None else None)
        result["memory_analysis_per_chip"] = per_chip
    out_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "aot_8b_result.json")
    # the key must capture EVERY knob that changes the numbers, or a
    # sweep overwrites the canonical rows SCALING.md cites
    key = "x".join(f"{k}{v}" for k, v in sorted(mesh_kwargs.items()))
    if topology != TOPOLOGY or num_slices > 1:
        key += f"-{topology}-s{num_slices}"
    if (batch, seq) != (BATCH, SEQ):
        key += f"-b{batch}-s{seq}"
    if args.model != "llama3_8b":
        key += f"-{args.model}"
    if args.virtual > 1:
        key += f"-v{args.virtual}"
    if args.generate:
        key += "-generate"
    try:
        with open(out_path, "r", encoding="utf-8") as f:
            all_results = json.load(f)
        if "mesh" in all_results:   # pre-dict format
            all_results = {}
    except (OSError, ValueError):
        all_results = {}
    all_results[key] = result
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(all_results, f, indent=2)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
