"""On-chip end-to-end: the full TonY chain driving a REAL TPU training job.

The CPU-mesh e2e suite (tests/test_e2e*.py, tests/test_examples.py) proves
the orchestrator logic the way the reference's MiniCluster suite did
(TestTonyE2E.java:89-484). What it cannot prove is the actual hardware
path: client -> AM -> executor -> a worker process that claims the axon
TPU tunnel and trains on the chip. This script is that missing leg:

  1. probe the tunnel (bench.py --probe) — skip cleanly if it is wedged;
  2. submit examples/llama-pretrain through the real TonyClient on the
     local backend with ONE worker (the tunnel is single-claim);
  3. the worker inherits the tunnel env (no JAX_PLATFORMS=cpu scrub —
     the exact opposite of the test suite) and trains on the TPU;
  4. assert SUCCEEDED + extract the worker's device line and final loss
     into tools/onchip_e2e_result.json.

Run it manually in a healthy-tunnel window:  python tools/onchip_e2e.py
Never run it concurrently with bench.py or the bench watcher's full run
(single-claim tunnel); a watcher *probe* colliding is harmless — the
probe loses the claim race and reports down, and this worker proceeds.
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

RESULT_PATH = os.environ.get(
    "TONY_ONCHIP_RESULT",
    os.path.join(REPO, "tools", "onchip_e2e_result.json"))


def _write(result: dict) -> None:
    import bench   # repo root is on sys.path; shares the stamp helper
    result["measured_at"] = time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
    result["commit"] = bench._commit_stamp()
    with open(RESULT_PATH, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def main() -> int:
    # 1. tunnel probe (subprocess so a wedge can't hang this script)
    try:
        probe = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--probe"],
            capture_output=True, text=True, timeout=150)
        probe_ok = "PROBE-OK" in probe.stdout
    except subprocess.TimeoutExpired:
        probe_ok = False
    if not probe_ok:
        _write({"ok": False, "skipped": "tunnel down at probe time"})
        return 1

    # 2. submit through the real client on the local backend
    from tony_tpu import constants as C
    from tony_tpu.client.tony_client import TonyClient
    from tony_tpu.conf import keys as K
    from tony_tpu.conf.configuration import TonyConfiguration

    steps = int(os.environ.get("TONY_ONCHIP_STEPS", "12"))
    model = os.environ.get("TONY_ONCHIP_CONFIG", "bench_350m")
    seq = int(os.environ.get("TONY_ONCHIP_SEQ", "1024"))
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="onchip_e2e_") as td:
        conf = TonyConfiguration()
        conf.set(K.CLUSTER_WORKDIR, os.path.join(td, "cluster"), "onchip")
        # generous ceilings: first compile through the tunnel is slow
        conf.set(K.APPLICATION_TIMEOUT, 1_500_000, "onchip")
        client = TonyClient(conf)
        client.init([
            "--executes",
            os.path.join(REPO, "examples", "llama-pretrain", "pretrain.py"),
            "--task_params",
            f"--config {model} --steps {steps} --batch-size 4 "
            f"--seq-len {seq}",
            "--conf", "tony.worker.instances=1",
            "--conf", "tony.application.framework=jax",
        ])
        client.run()

        # 3. evidence out of the worker's container log
        logs = ""
        croot = os.path.join(client.app_dir, C.CONTAINERS_DIR_NAME)
        for d, _, files in os.walk(croot):
            for f in files:
                if f in ("stdout", "stderr"):
                    with open(os.path.join(d, f), encoding="utf-8",
                              errors="replace") as fh:
                        logs += fh.read()[-8000:] + "\n"
        device = None
        m = re.search(r"devices: (\d+ x .+?) \(backend=(\w+)\)", logs)
        if m:
            device = {"devices": m.group(1), "backend": m.group(2)}
        loss = None
        m = re.search(r"final loss ([0-9.]+)", logs)
        if m:
            loss = float(m.group(1))
        on_tpu = bool(device) and device["backend"] not in ("cpu", "")
        ok = client.final_status == "SUCCEEDED" and on_tpu
        _write({
            "ok": ok,
            "final_status": client.final_status,
            "device": device,
            "final_loss": loss,
            "model": model, "steps": steps,
            "wall_s": round(time.monotonic() - t0, 1),
            "note": ("full client->AM->executor chain trained on the "
                     "real chip" if ok else
                     "chain ran but evidence incomplete — see fields"),
        })
        return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
